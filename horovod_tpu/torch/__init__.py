"""horovod_tpu.torch — PyTorch (CPU) binding over the native core.

Parity surface of reference horovod/torch/__init__.py: init/rank/size/
local_rank/local_size, sync+async+in-place collectives with autograd,
``DistributedOptimizer`` firing allreduce from gradient hooks as backward
produces them, ``broadcast_parameters`` / ``broadcast_optimizer_state``,
fp16 compression, ``backward_passes_per_step`` accumulation.

Process topology comes from the launcher's environment
(``horovod_tpu.run`` sets HOROVOD_RANK / HOROVOD_SIZE / HOROVOD_LOCAL_RANK
/ HOROVOD_LOCAL_SIZE / HOROVOD_CONTROLLER, replacing the reference's
mpirun-provided MPI_COMM_WORLD, operations.cc:1748-1797).
"""

from __future__ import annotations

import collections

import torch

from horovod_tpu.common.basics import check_extension
from horovod_tpu.common.launcher_env import native_init_kwargs
from horovod_tpu.native import NativeCore
from horovod_tpu.torch import mpi_ops
from horovod_tpu.torch.compression import Compression
from horovod_tpu.torch.mpi_ops import (
    allgather,
    allgather_async,
    allreduce,
    allreduce_,
    allreduce_async,
    allreduce_async_,
    broadcast,
    broadcast_,
    broadcast_async,
    broadcast_async_,
    poll,
    synchronize,
)


def init(comm=None) -> None:
    """Initialize the torch binding's native core from launcher env vars.

    Single-process (no launcher) degenerates to size 1, the reference's
    "no cluster needed" mode (SURVEY §4 mechanism 1).

    ``comm`` (reference ``hvd.init(comm=[ranks])``, common/__init__.py:
    58-84: restrict the job to a subset of MPI_COMM_WORLD) forms a
    sub-communicator: a collective rendezvous over the launcher's
    control star — the rank-address registry MPI groups provided for
    free — resolves each sub-world's coordinator, and this process then
    runs on a star/ring of just the members. Like ``MPI_Comm_split``,
    EVERY launched process must call ``init``; a process sitting the job
    out passes its own singleton (``comm=[hvd_world_rank]``). After
    init, ``rank()``/``size()`` report sub-world values (rank =
    position in ``comm``) and ``local_rank()``/``local_size()`` are
    regrouped among members by host.
    """
    if mpi_ops._core is not None and mpi_ops._core.initialized:
        return
    # HOROVOD_HIERARCHICAL_ALLREDUCE/ALLGATHER are consumed inside the
    # native core (csrc/coordinator.cc): it wires local/cross sub-rings and
    # runs the two-level ladder (reference operations.cc:1284-1436,
    # :929-1032), degrading to the flat ring for untileable topologies.
    core = NativeCore()
    core.init(comm=comm, **native_init_kwargs())
    mpi_ops._set_core(core)


def shutdown() -> None:
    if mpi_ops._core is not None:
        mpi_ops._core.shutdown()
        mpi_ops._set_core(None)


def rank() -> int:
    return mpi_ops._require_core().rank()


def size() -> int:
    return mpi_ops._require_core().size()


def local_rank() -> int:
    return mpi_ops._require_core().local_rank()


def local_size() -> int:
    return mpi_ops._require_core().local_size()


def mpi_threads_supported() -> bool:
    """No MPI anywhere in this framework (parity shim,
    reference operations.cc:2462-2468)."""
    mpi_ops._require_core()
    return False


# ------------------------------------------------------------------------
# DistributedOptimizer


class _DistributedOptimizer(torch.optim.Optimizer):
    """Mixin applied by dynamic subclassing in DistributedOptimizer().

    Behavior parity with reference torch/__init__.py:42-197: gradient
    hooks fire an async allreduce per parameter as autograd finishes each
    accumulation; ``synchronize()`` drains the handles and installs the
    averaged gradients; ``step()`` synchronizes then delegates;
    ``backward_passes_per_step`` delays the allreduce across N local
    backwards. The hook mechanism differs: torch >= 2.1 provides
    ``register_post_accumulate_grad_hook``, replacing the reference's
    grad_fn.next_functions accumulator hack (torch/__init__.py:95-130).
    """

    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step):
        super(self.__class__, self).__init__(params)
        self._compression = compression
        self._backward_passes_per_step = backward_passes_per_step
        if named_parameters is not None:
            named_parameters = list(named_parameters)
        else:
            named_parameters = [
                (f"allreduce.noname.{gi}.{pi}", v)
                for gi, group in enumerate(self.param_groups)
                for pi, v in enumerate(group["params"])]
        # Names must be unique: they key the negotiation
        # (reference torch/__init__.py:76-83).
        names = [n for n, _ in named_parameters]
        dups = [n for n, c in collections.Counter(names).items() if c > 1]
        if dups:
            raise ValueError(
                f"namespace of parameters is not unique: {dups}")
        self._parameter_names = {v: n for n, v in named_parameters}
        self._handles = {}
        self._ctxs = {}
        self._allreduce_delay = {}
        self._hook_refs = []
        if size() > 1:
            self._register_hooks()

    def _register_hooks(self):
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._allreduce_delay[p] = self._backward_passes_per_step
                    ref = p.register_post_accumulate_grad_hook(
                        self._make_hook())
                    self._hook_refs.append(ref)

    def _make_hook(self):
        def hook(p):
            assert not p.grad.requires_grad
            if self._allreduce_delay[p] <= 0:
                # A second backward would accumulate into a buffer the
                # background thread may still be reducing (reference
                # raises the same way, torch/__init__.py:115-123).
                raise AssertionError(
                    "Gradients were computed more than "
                    "backward_passes_per_step times before call to step(). "
                    "Increase backward_passes_per_step to accumulate "
                    "gradients locally.")
            self._allreduce_delay[p] -= 1
            if self._allreduce_delay[p] == 0:
                self._allreduce_grad_async(p)
        return hook

    def _allreduce_grad_async(self, p):
        name = self._parameter_names.get(p)
        compressed, ctx = self._compression.compress(p.grad.detach())
        handle = allreduce_async_(compressed, average=False, name=name)
        self._handles[p] = handle
        self._ctxs[p] = (compressed, ctx)

    def synchronize(self):
        """Wait for all gradient allreduces; install averaged grads
        (reference torch/__init__.py:132-147)."""
        # Parameters whose hook never fired (unused in the graph) must
        # still be reduced, or the other ranks deadlock
        # (reference test_force_allreduce, test_torch.py:1040-1108).
        for p, delay in list(self._allreduce_delay.items()):
            if p not in self._handles and delay > 0:
                if p.grad is None:
                    p.grad = torch.zeros_like(p)
                self._allreduce_grad_async(p)
        for p, handle in list(self._handles.items()):
            synchronize(handle)
            compressed, ctx = self._ctxs.pop(p)
            grad = self._compression.decompress(compressed, ctx)
            p.grad.copy_(grad).div_(size())
            self._allreduce_delay[p] = self._backward_passes_per_step
        self._handles.clear()

    def step(self, closure=None):
        if size() > 1:
            self.synchronize()
        return super(self.__class__, self).step(closure)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step=1):
    """Wrap a torch optimizer for data-parallel training.

    Dynamically subclasses the user's optimizer class so isinstance and
    attribute access keep working (reference torch/__init__.py:192-197).
    """
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step)


# ------------------------------------------------------------------------
# Parameter / optimizer-state bootstrap


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Broadcast a state_dict or iterable of (name, tensor) in place
    (reference torch/__init__.py:200-229)."""
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    handles = []
    for name, p in items:
        if p is None or not isinstance(p, torch.Tensor):
            continue
        handles.append(broadcast_async_(p, root_rank, name=name))
    for h in handles:
        synchronize(h)


def broadcast_object(obj, root_rank: int = 0, name: str = "broadcast_object"):
    """Broadcast an arbitrary picklable object (generalizes the
    reference's scalar wrapping, torch/__init__.py:273-348): pickle on
    root, ship length then payload as uint8 tensors."""
    import pickle

    if rank() == root_rank:
        payload = pickle.dumps(obj)
        length = torch.tensor([len(payload)], dtype=torch.int64)
    else:
        payload = b""
        length = torch.tensor([0], dtype=torch.int64)
    broadcast_(length, root_rank, name=f"{name}.len")
    buf = torch.empty(int(length.item()), dtype=torch.uint8)
    if rank() == root_rank:
        buf.copy_(torch.frombuffer(bytearray(payload), dtype=torch.uint8))
    broadcast_(buf, root_rank, name=f"{name}.data")
    return pickle.loads(bytes(buf.numpy().tobytes()))


def broadcast_optimizer_state(optimizer, root_rank: int = 0) -> None:
    """Synchronize full optimizer state from root, including non-tensor
    scalars (reference torch/__init__.py:232-348 wrapped each scalar into
    a tensor with recursive type-restoring callbacks; this rebuild ships
    one pickled state_dict and loads it, with in-place tensor broadcasts
    for the tensor leaves so devices/memory don't churn)."""
    if isinstance(optimizer, torch.optim.LBFGS):
        raise ValueError("cannot broadcast torch.optim.LBFGS state")
    if size() == 1:
        return
    state_dict = optimizer.state_dict()
    # Newly constructed optimizers have empty state; the reference
    # initialized it on every rank by running a zero-gradient step
    # (torch/__init__.py:249-262) — every rank constructs the optimizer
    # identically, so the emptiness check is globally consistent.
    if not state_dict.get("state"):
        for group in optimizer.param_groups:
            for p in group["params"]:
                if p.requires_grad and p.grad is None:
                    p.grad = torch.zeros_like(p)
        optimizer.step()
        state_dict = optimizer.state_dict()

    full = broadcast_object(state_dict, root_rank,
                            name="optimizer_state_dict")
    if rank() != root_rank:
        optimizer.load_state_dict(full)


__all__ = [
    "init", "shutdown", "rank", "size", "local_rank", "local_size",
    "mpi_threads_supported", "check_extension",
    "allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
    "allgather", "allgather_async",
    "broadcast", "broadcast_", "broadcast_async", "broadcast_async_",
    "poll", "synchronize", "Compression", "DistributedOptimizer",
    "broadcast_parameters", "broadcast_optimizer_state", "broadcast_object",
]
