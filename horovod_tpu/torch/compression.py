"""Gradient compression for the torch binding.

Parity surface of reference horovod/torch/compression.py (same scheme as
tensorflow/compression.py:33-74): ``none`` passes through, ``fp16`` casts
floating tensors to half for the wire and back after.
"""

from __future__ import annotations

import torch


class Compressor:
    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """No compression (reference compression.py:33-43)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast to fp16 before the collective, back after
    (reference compression.py:46-74)."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if tensor.dtype.is_floating_point:
            tensor = tensor.to(torch.float16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None and ctx.is_floating_point and tensor.dtype != ctx:
            tensor = tensor.to(ctx)
        return tensor


class Compression:
    """Namespace mirroring ``hvd.Compression.none`` / ``.fp16``."""

    none = NoneCompressor
    fp16 = FP16Compressor
