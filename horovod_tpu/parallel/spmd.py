"""The SPMD rank harness: "every chip is a rank".

This is the central TPU-native design move. The reference ran N OS processes
that dynamically negotiated tensor readiness over MPI (horovod/common/
operations.cc:2030-2380). Under XLA there is one traced program executed by
every chip, so the negotiation protocol collapses: collectives execute in
compiled program order. What remains is giving the user the Horovod
*programming model* — "my code runs once per rank, `hvd.rank()` tells me
which, `hvd.allreduce()` combines" — which maps exactly onto
``jax.shard_map`` over a 1-D device mesh.

``spmd_run(fn, *args)`` traces ``fn`` once with the "hvd" axis active;
inside, :func:`horovod_tpu.rank` is the traced chip index and the collective
ops in :mod:`horovod_tpu.jax.mpi_ops` lower to ``lax.psum``/``all_gather``/
``all_to_all`` on the ICI.

This harness is also how the reference's mpirun-launched, size-parametric
tests (reference test/test_torch.py, run under ``mpirun -np N``) port to a
single host: the same closed-form assertions run over an N-chip mesh.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.common import state as _state
from horovod_tpu.parallel.logical import DATA_AXIS

# jax.shard_map is the public top-level API on current jax (with the
# varying-manual-axes checker spelled ``check_vma``); older jax ships
# the same transform as jax.experimental.shard_map.shard_map with the
# checker's predecessor spelled ``check_rep``. Resolve once at import so
# the whole hvd.* dispatch harness (and everything built on it: bench,
# the window loop, the gate lanes) runs on both. The checker kwarg is
# read off the resolved function's OWN signature — promotion and rename
# did not land in the same jax release, so inferring one from the other
# would TypeError on the in-between versions.
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect as _inspect

_SHARD_MAP_CHECK_KW = (
    "check_vma"
    if "check_vma" in _inspect.signature(_shard_map).parameters
    else "check_rep")


def _default_mesh() -> Mesh:
    st = _state.global_state()
    st.require_init()
    return st.mesh


def axis_size(mesh: Optional[Mesh] = None, axis: str = DATA_AXIS) -> int:
    mesh = mesh or _default_mesh()
    return mesh.shape[axis]


def spmd_fn(
    fn,
    *,
    mesh: Optional[Mesh] = None,
    axis_name: str = DATA_AXIS,
    in_specs: Any = P(),
    out_specs: Any = P(),
    # False BY DESIGN (not a leftover): this harness implements the
    # Horovod programming model — "my code runs once per rank" — whose
    # outputs are routinely rank-varying (rank(), per-rank metrics,
    # per-shard BN statistics) under caller-chosen out_specs; the
    # varying-manual-axes checker statically rejects exactly that
    # pattern. Raw jax.shard_map call sites across the repo run with the
    # checker ON (see docs/parallelism.md); callers of this harness can
    # opt in via check_vma=True when their fn is fully typed.
    check_vma: bool = False,
    jit: bool = True,
    donate_argnums=(),
    host_local: bool = True,
):
    """Build (once) the compiled SPMD form of ``fn``.

    ``host_local`` (multi-host only): when True (default, the Horovod
    programming model) every process passes its host-local input shard and
    receives host-local outputs — each dispatch converts to/from global
    jax.Arrays. That round-trip reshards the ENTIRE argument list every
    step and breaks the donation chain for carried state; training loops
    that thread a large state through consecutive calls should pass
    ``host_local=False`` and keep global, already-sharded jax.Arrays
    (outputs feed back in unchanged), paying the conversion only at the
    loop boundary.

    Returns ``jit(shard_map(fn'))`` where ``fn'`` activates the "hvd"
    collective axis for :mod:`horovod_tpu.jax.mpi_ops` at trace time. Build
    this once and call it every step — the XLA executable is cached, which
    is the TPU analogue of the reference's compiled graph ops being built
    once per tensor name (horovod/tensorflow/mpi_ops.py:73-91).

    ``donate_argnums`` is forwarded to ``jax.jit``: donate the train-state
    argument of a training step so XLA reuses its device buffers for the
    updated state instead of allocating a fresh copy every step (the
    in-place-update analogue of the reference's in-place ``MPI_IN_PLACE``
    allreduce path, operations.cc:1574-1584 — but for the whole model).

    When ``HOROVOD_TIMELINE`` is active, each returned handle emits
    ``XLA_COMPILE`` around its first dispatch (trace+compile happen there,
    so that span is the real compile cost) and ``XLA_EXECUTE`` around every
    subsequent dispatch. jax dispatch is asynchronous, so the XLA_EXECUTE
    span measures HOST DISPATCH time (the analogue of the reference's
    QUEUE activity), not device execution — the events carry
    ``args.span = "host_dispatch"`` to say so; use ``jax.profiler`` for
    device-side op time. Taxonomy parity: reference operations.h:29-50,
    docs/timeline.md:17-62.
    """
    mesh = mesh or _default_mesh()

    def _build_shmapped():
        """A FRESH wrapper object per build: jax's tracing caches key on
        callable identity, so re-jitting the same shard_map object would
        silently reuse the old traced program — a rebuild must start from
        a new chain for a changed fusion threshold to re-trace into a new
        bucket plan."""

        @functools.wraps(fn)
        def wrapped(*inner):
            token = _state.set_spmd_axis(axis_name)
            st = _state.global_state()
            # Expose THIS handle's host_local mode for the duration of the
            # trace (runs at trace time, so any trace path — dispatch or
            # the AOT ._compiled.lower() escape hatch — sees the right
            # value; trace-time consumers like the ZeRO optimizer use it
            # to reject the default host-local conversion on multi-host).
            saved_hl = getattr(st, "dispatch_host_local", True)
            st.dispatch_host_local = host_local
            try:
                return fn(*inner)
            finally:
                st.dispatch_host_local = saved_hl
                _state.reset_spmd_axis(token)

        return _shard_map(
            wrapped,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            **{_SHARD_MAP_CHECK_KW: check_vma},
        )

    shmapped = _build_shmapped()
    if not jit:
        return shmapped

    track = getattr(fn, "__name__", "spmd_fn")
    compiled_once = [False]

    def _globalize(args):
        """Multi-host entry: each process passes its HOST-LOCAL shard
        (the Horovod programming model — every process loads its own
        slice of the batch); assemble them into global jax.Arrays over
        the full mesh. Single-process jobs skip this entirely."""
        from jax.experimental import multihost_utils

        return multihost_utils.host_local_array_to_global_array(
            tuple(args), mesh, in_specs
        )

    def _localize(out):
        from jax.experimental import multihost_utils

        return multihost_utils.global_array_to_host_local_array(
            out, mesh, out_specs
        )
    # Box the jit handle so the HOROVOD_AUTOTUNE tuner can force a re-trace
    # (a fresh jit wrapper) when it changes the fusion threshold — the
    # threshold is read at trace time by horovod_tpu.jax.fusion, so a new
    # bucket plan needs a new program. built_gen tracks which tuner
    # generation this handle's program was traced under.
    compiled_box = [jax.jit(shmapped, donate_argnums=donate_argnums)]
    built_gen = [None]

    @functools.wraps(fn)
    def dispatch(*args, **kwargs):
        st = _state.global_state()
        tuner = getattr(st, "autotuner", None)
        # Re-jit whenever the tuner's generation moved — including the FINAL
        # bump that accompanies convergence, which is what applies the
        # winning threshold (converged flips and generation increments in
        # the same end_window call; gating this on `not converged` would
        # leave the last swept candidate's bucket plan in place forever).
        if tuner is not None and built_gen[0] != tuner.generation:
            if built_gen[0] is None:
                built_gen[0] = tuner.generation  # first build already fresh
            else:
                compiled_box[0] = jax.jit(
                    _build_shmapped(), donate_argnums=donate_argnums
                )
                built_gen[0] = tuner.generation
                compiled_once[0] = False
                dispatch._compiled = compiled_box[0]

        multi_host = host_local and st.process_count > 1
        if multi_host:
            args = _globalize(args)

        tl = getattr(st, "timeline", None)
        if tl is None or not tl.enabled:
            out = compiled_box[0](*args, **kwargs)
            compiled_once[0] = True
        else:
            from horovod_tpu.utils import timeline as _tl_names

            # The first dispatch blocks through trace+compile (a real
            # span); later spans time only the async host dispatch.
            act = (_tl_names.XLA_EXECUTE if compiled_once[0]
                   else _tl_names.XLA_COMPILE)
            span = "host_dispatch" if compiled_once[0] else "trace+compile"
            tl.start(track, act, args={"span": span})
            try:
                out = compiled_box[0](*args, **kwargs)
            finally:
                tl.end(track, act)
                compiled_once[0] = True

        if (
            tuner is not None
            and not tuner.converged
            and tuner.claim(dispatch)
            and tuner.step_done()
        ):
            # The tuner blocks AND forces a d2h pull before reading its
            # clock (sync-honest probe; see StepAutotuner.end_window).
            tuner.end_window(out)
        if multi_host:
            out = _localize(out)
        return out

    dispatch._compiled = compiled_box[0]  # escape hatch for AOT (.lower) users
    return dispatch


# (fn, mesh, axis, specs, check_vma) -> compiled, bounded LRU. The compiled
# callable closes over fn, so weak keying can never evict; a hard cap keeps
# per-call lambdas from accumulating executables without bound. Callers who
# want cache hits must pass a stable fn object (same contract as jax.jit).
_SPMD_CACHE_MAX = 128
_spmd_cache: "dict" = {}


def _hashable_specs(specs):
    if isinstance(specs, (list, tuple)):
        return tuple(_hashable_specs(s) for s in specs)
    if isinstance(specs, dict):
        return tuple(sorted((k, _hashable_specs(v)) for k, v in specs.items()))
    return specs


def spmd_run(
    fn,
    *args,
    mesh: Optional[Mesh] = None,
    axis_name: str = DATA_AXIS,
    in_specs: Any = P(),
    out_specs: Any = P(),
    check_vma: bool = False,
):
    """Run ``fn(*args)`` as a per-chip SPMD program.

    Defaults treat inputs as replicated (every rank sees the same value, the
    way every Horovod process loads the same script state) and require
    outputs to be rank-invariant (e.g. allreduce results). Pass
    ``out_specs=P("hvd")`` (or a pytree of specs) for per-rank outputs:
    they come back concatenated along their leading axis, exactly like the
    reference's allgathered test assertions.

    The compiled executable is cached per (fn, mesh, specs): repeated calls
    with the same ``fn`` object re-dispatch without re-tracing.
    """
    mesh = mesh or _default_mesh()
    try:
        key = (fn, mesh, axis_name, _hashable_specs(in_specs), _hashable_specs(out_specs), check_vma)
        compiled = _spmd_cache.pop(key, None)  # pop+reinsert = LRU touch
    except TypeError:  # unhashable fn or specs: build uncached
        key = None
        compiled = None
    if compiled is None:
        compiled = spmd_fn(
            fn,
            mesh=mesh,
            axis_name=axis_name,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
        )
    if key is not None:
        _spmd_cache[key] = compiled
        while len(_spmd_cache) > _SPMD_CACHE_MAX:
            _spmd_cache.pop(next(iter(_spmd_cache)))
    return compiled(*args)


def spmd(
    fn=None,
    *,
    mesh: Optional[Mesh] = None,
    axis_name: str = DATA_AXIS,
    in_specs: Any = P(),
    out_specs: Any = P(),
    check_vma: bool = False,
):
    """Decorator form of :func:`spmd_run`.

    ``mesh`` is resolved at call time so the decorator can be applied at
    import time, before ``hvd.init()``.
    """

    def deco(f):
        # Keyword arguments are bound as (replicated) closure constants —
        # shard_map partitions only the positional inputs. Reuse one partial
        # per kwargs combination so repeated calls hit the spmd_run cache
        # instead of re-tracing every step.
        partials: dict = {}

        @functools.wraps(f)
        def caller(*args, **kwargs):
            if kwargs:
                try:
                    pkey = tuple(sorted(kwargs.items()))
                    fn = partials.get(pkey)
                    if fn is None:
                        fn = partials[pkey] = functools.partial(f, **kwargs)
                except TypeError:  # unhashable kwarg: no caching possible
                    fn = functools.partial(f, **kwargs)
            else:
                fn = f
            return spmd_run(
                fn,
                *args,
                mesh=mesh,
                axis_name=axis_name,
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=check_vma,
            )

        return caller

    if fn is None:
        return deco
    return deco(fn)
