"""The SPMD rank harness: "every chip is a rank".

This is the central TPU-native design move. The reference ran N OS processes
that dynamically negotiated tensor readiness over MPI (horovod/common/
operations.cc:2030-2380). Under XLA there is one traced program executed by
every chip, so the negotiation protocol collapses: collectives execute in
compiled program order. What remains is giving the user the Horovod
*programming model* — "my code runs once per rank, `hvd.rank()` tells me
which, `hvd.allreduce()` combines" — which maps exactly onto
``jax.shard_map`` over a 1-D device mesh.

``spmd_run(fn, *args)`` traces ``fn`` once with the "hvd" axis active;
inside, :func:`horovod_tpu.rank` is the traced chip index and the collective
ops in :mod:`horovod_tpu.jax.mpi_ops` lower to ``lax.psum``/``all_gather``/
``all_to_all`` on the ICI.

This harness is also how the reference's mpirun-launched, size-parametric
tests (reference test/test_torch.py, run under ``mpirun -np N``) port to a
single host: the same closed-form assertions run over an N-chip mesh.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.common import state as _state


def _default_mesh() -> Mesh:
    st = _state.global_state()
    st.require_init()
    return st.mesh


def axis_size(mesh: Optional[Mesh] = None, axis: str = "hvd") -> int:
    mesh = mesh or _default_mesh()
    return mesh.shape[axis]


def spmd_run(
    fn,
    *args,
    mesh: Optional[Mesh] = None,
    axis_name: str = "hvd",
    in_specs: Any = P(),
    out_specs: Any = P(),
    check_vma: bool = False,
):
    """Run ``fn(*args)`` as a per-chip SPMD program.

    Defaults treat inputs as replicated (every rank sees the same value, the
    way every Horovod process loads the same script state) and require
    outputs to be rank-invariant (e.g. allreduce results). Pass
    ``out_specs=P("hvd")`` (or a pytree of specs) for per-rank outputs:
    they come back concatenated along their leading axis, exactly like the
    reference's allgathered test assertions.
    """
    mesh = mesh or _default_mesh()

    @functools.wraps(fn)
    def wrapped(*inner):
        token = _state.set_spmd_axis(axis_name)
        try:
            return fn(*inner)
        finally:
            _state.reset_spmd_axis(token)

    shmapped = jax.shard_map(
        wrapped,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=check_vma,
    )
    return shmapped(*args)


def spmd(
    fn=None,
    *,
    mesh: Optional[Mesh] = None,
    axis_name: str = "hvd",
    in_specs: Any = P(),
    out_specs: Any = P(),
    check_vma: bool = False,
):
    """Decorator form of :func:`spmd_run`.

    ``mesh`` is resolved at call time so the decorator can be applied at
    import time, before ``hvd.init()``.
    """

    def deco(f):
        @functools.wraps(f)
        def caller(*args, **kwargs):
            # Keyword arguments are bound as (replicated) closure constants:
            # shard_map partitions only the positional inputs.
            fn = functools.partial(f, **kwargs) if kwargs else f
            return spmd_run(
                fn,
                *args,
                mesh=mesh,
                axis_name=axis_name,
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=check_vma,
            )

        return caller

    if fn is None:
        return deco
    return deco(fn)
