"""Ring attention: exact attention over sequences sharded across chips.

First-class long-context support (beyond the reference, which scaled batch
only — SURVEY §2.9/§5). Each chip holds a sequence shard of Q, K, V; K/V
blocks rotate around the mesh axis with ``lax.ppermute`` while every chip
accumulates its queries' attention over each visiting block with the
online-softmax (flash) recurrence. Peak memory is O(L_local^2) per step
instead of O(L^2), and the ICI transfer of the next block overlaps the
current block's compute (XLA schedules the ppermute concurrently with the
einsums — the Pallas guide's ring-collective pattern). In causal mode a
visiting block entirely above this shard's diagonal skips its compute
(the ring-level twin of the flash kernels' causal grid truncation); the
rotation itself is never skipped — collectives stay rank-uniform.

Use inside ``shard_map``/``spmd_run`` with the sequence axis sharded, e.g.
``in_specs=P(None, "sp", None, None)`` for [B, L, H, D].
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.ops.attention import NEG_INF
from horovod_tpu.parallel.logical import module_axis


def ring_attention(q, k, v, axis: Optional[str] = None, causal: bool = False,
                   scale: Optional[float] = None,
                   skip_dead_blocks: Optional[bool] = None):
    """Exact multi-head attention over a sequence-sharded mesh axis.

    Shapes (per chip): q, k, v [B, L_local, H, D] -> [B, L_local, H, D].
    Must run inside a shard_map region with ``axis`` active. Causal masks
    use global token positions, so results match single-chip attention on
    the gathered sequence exactly.

    ``skip_dead_blocks`` (causal only) conditionally skips the einsums
    for visiting blocks entirely above this shard's diagonal. The
    default (None) enables it exactly when the runtime's vma typing can
    transpose the rank-divergent cond (see the in-loop note); the
    explicit values exist for A/B and for CI on legacy runtimes, where
    the cond path is only legal under ``check_vma=False`` regions.
    """
    axis = module_axis("seq", axis)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    size = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    B, Lq, H, D = q.shape
    Lk = k.shape[1]

    qf = q.astype(jnp.float32) * scale
    perm = [(i, (i + 1) % size) for i in range(size)]

    def step(p, carry):
        k_blk, v_blk, m, l, acc = carry
        src = (rank - p) % size  # owner of the block currently held

        def _update(operand):
            k_b, v_b, m, l, acc = operand
            s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_b.astype(jnp.float32))
            if causal:
                q_pos = rank * Lq + jnp.arange(Lq)[:, None]
                k_pos = src * Lk + jnp.arange(Lk)[None, :]
                s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            alpha = jnp.exp(m - m_new)
            p_exp = jnp.exp(s - m_new[..., None])
            l_new = l * alpha + jnp.sum(p_exp, axis=-1)
            acc_new = acc * alpha[..., None] + jnp.einsum(
                "bhqk,bkhd->bhqd", p_exp, v_b.astype(jnp.float32))
            return m_new, l_new, acc_new

        if causal and skip_dead_blocks:
            # The dead half of the causal ring: a visiting block whose
            # FIRST global key position is past this shard's LAST query
            # row is fully masked — skip its einsums and rescale
            # outright (same at-or-below-diagonal discipline as the
            # flash kernels' truncated grid; ~half the ring steps on a
            # causal square). Only the local compute is conditional:
            # the ppermute rotation below stays unconditional, since
            # every rank must feed the collective on every step. Off by
            # default on legacy (no-vma-typing) runtimes: the check_rep
            # machinery cannot unify this rank-divergent cond's
            # TRANSPOSE (dead-branch symbolic-zero cotangents type
            # replicated), so there the unconditional — numerically
            # identical — masked update runs instead; CI still pins the
            # cond path through check_vma=False regions.
            has_live = rank * Lq + Lq - 1 >= src * Lk
            m, l, acc = lax.cond(has_live, _update,
                                 lambda operand: operand[2:],
                                 (k_blk, v_blk, m, l, acc))
        else:
            m, l, acc = _update((k_blk, v_blk, m, l, acc))
        # Rotate K/V to the next chip; the final rotation returns blocks
        # home, keeping the loop body uniform for lax.fori_loop.
        k_next = lax.ppermute(k_blk, axis, perm)
        v_next = lax.ppermute(v_blk, axis, perm)
        return k_next, v_next, m, l, acc

    from horovod_tpu.parallel._vma import match_vma, vma_typing_available

    if skip_dead_blocks is None:
        skip_dead_blocks = vma_typing_available()

    # Type the zero-init carries as varying like q/k/v so the loop body's
    # carry-out matches under check_vma=True (values unchanged).
    m0 = match_vma(jnp.full((B, H, Lq), NEG_INF, jnp.float32), q, k, v)
    l0 = match_vma(jnp.zeros((B, H, Lq), jnp.float32), q, k, v)
    acc0 = match_vma(jnp.zeros((B, H, Lq, D), jnp.float32), q, k, v)
    _, _, m, l, acc = lax.fori_loop(0, size, step, (k, v, m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B, H, Lq, D]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)
