"""Varying-manual-axes (vma) plumbing for shard_map's static checker.

Under ``jax.shard_map(..., check_vma=True)`` (the default) every value
inside the region is typed with the set of mesh axes it varies over, and
scan carries / custom-VJP rules must produce exactly-matching types.
These helpers mark values as varying to satisfy the checker; they are
no-ops outside shard_map and under ``check_vma=False`` (``lax.pcast``
is identity-valued — it only changes the static type).
"""

from __future__ import annotations

import jax
from jax import lax


def vma_of(*arrays) -> frozenset:
    """Union of the varying mesh axes of ``arrays`` (empty outside
    shard_map / on non-traced values)."""
    union: frozenset = frozenset()
    for a in arrays:
        try:
            union = union | jax.typeof(a).vma
        except (AttributeError, TypeError):
            pass
    return union


try:
    from jax._src import config as _jax_config

    _CHECK_VMA_FLAG = _jax_config._check_vma
except (ImportError, AttributeError):  # pragma: no cover - jax internals
    _CHECK_VMA_FLAG = None


def vma_typing_available() -> bool:
    """Whether this jax types shard_map values with varying-manual-axes
    (the check_vma regime). Legacy runtimes (check_rep era) return
    False. Used to gate optimizations whose transpose rules only
    type-check under vma — e.g. ring attention's causal dead-block skip
    is a rank-divergent ``lax.cond`` whose GRADIENT the old check_rep
    machinery cannot unify (its own error suggests check_rep=False)."""
    return _CHECK_VMA_FLAG is not None


def vma_checking() -> bool:
    """Whether the enclosing shard_map traces with check_vma=True.

    jax exposes the regional setting through its config during tracing
    (the same flag Pallas consults). There is NO safe silent fallback:
    the typed and untyped gradient regimes need opposite reductions
    (see :func:`reduce_cotangent`), so guessing wrong silently scales
    gradients by the axis size — if a jax upgrade moves the internal,
    fail loudly here instead. Pinned by
    tests/test_parallel.py::test_vma_checking_tracks_region."""
    if _CHECK_VMA_FLAG is None:
        if not hasattr(jax, "typeof"):
            # Legacy runtime (no jax.typeof): vma TYPING does not exist
            # at all, so the enclosing shard_map can only be the
            # untyped regime — a fact, not a guess. The untyped-branch
            # reductions are pinned against dense gold on exactly these
            # runtimes (tests/test_parallel_lm.py dense-parity suite).
            return False
        raise RuntimeError(
            "jax no longer exposes jax._src.config._check_vma; "
            "horovod_tpu.parallel._vma.vma_checking must be updated for "
            "this jax version (guessing would silently mis-scale "
            "gradients)")
    return bool(_CHECK_VMA_FLAG.value)


def reduce_cotangent(g, axis: str, mean: bool, invariant_loss: bool = False):
    """Reduce a replicated parameter's cotangent over ``axis``,
    correctly in BOTH shard_map gradient regimes (all cases measured in
    __graft_entry__'s closed-form gate work).

    Untyped (check_vma=False): the backward leaves this rank's partial
    in the cotangent regardless of the loss's form — apply the
    psum/pmean ourselves.

    Typed (check_vma=True): jax's machinery already reduced over every
    axis the param is invariant on, but WHAT is in hand depends on the
    loss the caller differentiated (``invariant_loss``):

    * loss already collectively meaned over ``axis`` (e.g. wrapped in
      ``lax.pmean`` inside the loss fn) -> the cotangent IS the exact
      mean-loss gradient: identity.
    * loss varying per rank (no collective inside) -> the cotangent is
      the gradient of the rank-SUM: a mean still needs the /n.

    A cotangent still varying over ``axis`` is genuinely per-rank in
    either regime — reduce it ourselves.
    """
    if not vma_checking() or axis in vma_of(g):
        return lax.pmean(g, axis) if mean else lax.psum(g, axis)
    if invariant_loss:
        return g
    return g / lax.axis_size(axis) if mean else g


def scale_sharded_cotangent(g, axis: str, invariant_loss: bool = False):
    """Normalize an axis-SHARDED param's cotangent toward the MEAN of
    the per-rank loss terms.

    No collective belongs here (ranks hold different shards — e.g.
    different experts; the backward all_to_all already routed every
    rank's contribution to the owner); only the scale differs by
    regime × loss form. The cotangent is the n-times-counted SUM of the
    per-rank terms — divide by the axis size — EXCEPT in the typed
    regime with a loss the caller already collectively meaned
    (``invariant_loss=True``), where it is the exact mean-loss gradient
    already. All cases measured in __graft_entry__'s EP closed-form
    gate and tests/test_parallel_lm.py's MoE-vs-dense check."""
    if invariant_loss and vma_checking():
        return g
    return g / lax.axis_size(axis)


def match_vma(x, *refs):
    """Mark ``x`` varying over every axis the ``refs`` vary over.

    The canonical use is typing a ``jnp.zeros`` initial scan carry to
    match the loop body's output (the checker requires carry-in ==
    carry-out types)."""
    missing = vma_of(*refs) - vma_of(x)
    if missing:
        x = lax.pcast(x, tuple(missing), to="varying")
    return x
