"""Tensor parallelism: Megatron-style column/row-parallel layers.

Beyond-reference capability (SURVEY §2.9: the reference has no sharded
matmul anywhere): weight matrices shard over a ``"tp"`` mesh axis so the
MXU works on large local matmuls and only activations cross the ICI. The
canonical MLP pattern — column-parallel up-projection (no comm), row-
parallel down-projection (one psum) — costs exactly one allreduce per
block, and composes with the DP gradient allreduce over an orthogonal
mesh axis.

Functional helpers assume they run inside shard_map with weights passed
pre-sharded via in_specs (e.g. ``P(None, "tp")`` for a column split).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


from functools import partial

from horovod_tpu.parallel.logical import module_axis


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_region_input(x, axis: str):
    """Megatron's ``f`` conjugate: identity forward, psum backward.

    Place on the replicated activation ENTERING a column-parallel region
    whenever parameters live upstream (embeddings, layernorms, previous
    blocks): each tp rank's backward only carries the cotangent of its
    own head/feature shard, so without this psum the upstream gradients
    would single-count the sharded paths. The forward-psum of
    :func:`row_parallel` is the matching ``g`` on the way out. Costs
    nothing in forward; one allreduce in backward."""
    return x


def _tp_region_fwd(x, axis):
    return x, None


def _tp_region_bwd(axis, _, g):
    from horovod_tpu.parallel._vma import vma_checking, vma_of

    if vma_checking():
        # Typed (check_vma=True) mode: the transpose of jax's
        # auto-inserted pvary has ALREADY reduced the cotangent over
        # every axis the primal was invariant on — psumming again would
        # scale gradients by the axis size. Reduce ourselves only when
        # the cotangent still carries per-rank values over `axis`.
        if axis in vma_of(g):
            return (lax.pcast(lax.psum(g, axis), axis, to="varying"),)
        return (g,)
    # Untyped (check_vma=False) mode: no auto-insertion happens, the
    # cotangent holds this rank's partial — the conjugate owns the psum.
    return (lax.psum(g, axis),)


tp_region_input.defvjp(_tp_region_fwd, _tp_region_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_region_output(x, axis: str):
    """Megatron's ``g`` conjugate: psum forward, identity backward.

    The correct VJP for a cross-rank sum whose output is consumed as a
    replicated value: the true Jacobian w.r.t. each rank's partial is 1,
    so the replicated cotangent passes through unchanged. Differentiating
    through a RAW ``lax.psum`` instead applies psum again in the
    transpose (the classic pmap/shard_map gotcha), silently scaling every
    upstream gradient by the axis size — which is why every
    explicitly-summed parallel region here must use this (or
    :func:`sum_across` for scalars) rather than bare psum when gradients
    flow."""
    return lax.psum(x, axis)


def _tp_out_fwd(x, axis):
    return lax.psum(x, axis), None


def _tp_out_bwd(axis, _, g):
    # Identity value (the true Jacobian of a cross-rank sum consumed as
    # replicated), typed varying to match the per-rank primal input.
    return (lax.pcast(g, axis, to="varying"),)


tp_region_output.defvjp(_tp_out_fwd, _tp_out_bwd)

# General-purpose alias: a differentiable cross-rank sum (e.g. loss
# terms summed over a sequence-parallel axis).
sum_across = tp_region_output


def column_parallel(x, w, b=None, axis: Optional[str] = None,
                    gather_output: bool = False):
    """y_local = x @ W_local where W is column-sharded [Din, Dout/P].

    No communication; each chip produces its slice of the output features.
    ``gather_output=True`` all-gathers feature slices (when the next layer
    is not row-parallel). ``axis=None`` resolves the tensor axis through
    the bound :class:`~horovod_tpu.parallel.logical.LogicalMesh` (legacy
    ``"tp"`` when none is bound).
    """
    axis = module_axis("tensor", axis)
    y = x @ w
    if b is not None:
        y = y + b
    if gather_output:
        y = lax.all_gather(y, axis, axis=y.ndim - 1, tiled=True)
    return y


def row_parallel(x, w, b=None, axis: Optional[str] = None):
    """y = psum_p(x_local @ W_local) where W is row-sharded [Din/P, Dout]
    and x is feature-sharded to match a preceding column-parallel layer.

    One psum produces the full output on every chip; the bias is added
    once after the reduction. The sum rides :func:`tp_region_output` so
    gradients through it are exact (identity backward), not axis-size
    scaled."""
    axis = module_axis("tensor", axis)
    y = tp_region_output(x @ w, axis)
    if b is not None:
        y = y + b
    return y


def tp_mlp(x, w_up, b_up, w_down, b_down, axis: Optional[str] = None,
           activation: Callable = jax.nn.gelu):
    """The canonical 2-layer TP block: column-parallel up (no comm), local
    activation, row-parallel down (one psum)."""
    axis = module_axis("tensor", axis)
    h = activation(column_parallel(x, w_up, b_up, axis))
    return row_parallel(h, w_down, b_down, axis)


def vocab_parallel_logits(x, head, axis: Optional[str] = None):
    """Full-vocab logits from a column-sharded head: ``x @ W_local``
    ([..., E] x [E, V/P]) then ONE tiled all-gather over the vocab
    axis — exactly :func:`column_parallel` with ``gather_output``.

    The inference-side conjugate of ops/xent.py's vocab-parallel loss
    (which never materializes full logits): serving needs the whole
    row because the SAMPLER (greedy argmax, top-k) runs host-side over
    full-vocab f32. Each chip computes its vocab columns with the
    bit-identical dot products of the dense ``x @ W`` — the gather
    only concatenates slices in axis order — so greedy decode over a
    sharded head stays token-exact vs the replicated reference
    (tests/test_serve_engine.py pins it)."""
    return column_parallel(x, head, axis=axis, gather_output=True)


def shard_columns(w, axis_size: int, index: int):
    """Host-side helper: slice the column shard for mesh position
    ``index`` (used when materializing per-chip weights outside
    shard_map)."""
    cols = w.shape[-1] // axis_size
    return w[..., index * cols:(index + 1) * cols]


def shard_rows(w, axis_size: int, index: int):
    rows = w.shape[0] // axis_size
    return w[index * rows:(index + 1) * rows]
