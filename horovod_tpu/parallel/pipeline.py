"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

Beyond-reference capability (SURVEY §2.9: no stage scheduling anywhere in
the reference). SPMD formulation: every chip runs the same program; chip
``r`` of the ``"pp"`` axis applies stage ``r``; activations hop to the
next stage with ``lax.ppermute`` each tick. With M microbatches and P
stages the schedule runs M + P - 1 ticks (the classic GPipe bubble of
(P-1)/(M+P-1)); ICI transfers overlap the next tick's compute.

Stage weights are passed stacked over the leading axis and sharded with
``in_specs=P("pp")`` so each chip holds only its stage.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.logical import module_axis


def pipeline_apply(stage_fn: Callable, stage_params: Any, x,
                   axis: Optional[str] = None, remat: bool = False):
    """Run a P-stage pipeline over microbatches inside shard_map.

    Args:
      stage_fn: ``(params_for_stage, activation) -> activation`` — the same
        callable for every stage (heterogeneous stages: dispatch on a
        param field). Activation shape must be stage-invariant.
      stage_params: this chip's stage weights (pass stacked [P, ...] with
        ``P("pp")`` in_specs; shard_map strips the leading axis — if the
        per-chip view keeps a leading singleton, it is squeezed).
      x: this call's microbatch stack [M, ...micro_shape] (replicated).
      remat: rematerialize each stage application in the backward pass
        (``jax.checkpoint``). Under autodiff the schedule stores one
        activation per tick; remat drops the intra-stage intermediates
        and recomputes them, cutting pipeline activation memory to
        ~O(ticks x activation) — the TPU-idiomatic answer to 1F1B's
        memory goal (trade FLOPs for HBM, keep the one-program SPMD
        schedule).

    Returns [M, ...out_shape]: outputs of the final stage, replicated via
    a final broadcast psum so every chip returns the same value.
    """
    axis = module_axis("stage", axis)
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    size = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    M = x.shape[0]

    params = stage_params
    leaves = jax.tree_util.tree_leaves(params)
    if leaves and all(l.shape[:1] == (1,) for l in leaves):
        params = jax.tree_util.tree_map(lambda l: l[0], params)

    perm = [(i, (i + 1) % size) for i in range(size)]
    micro_shape = x.shape[1:]
    n_ticks = M + size - 1

    def tick(t, carry):
        current, outputs = carry
        # Stage 0 injects microbatch t (while t < M); other stages use the
        # activation received from the previous stage.
        inject = jnp.where(t < M, t, M - 1)
        current = jnp.where(rank == 0, x[inject], current)
        result = stage_fn(params, current)
        # The last stage emits microbatch t - (P - 1) at tick t.
        out_idx = t - (size - 1)
        emit = jnp.logical_and(rank == size - 1, out_idx >= 0)
        safe_idx = jnp.clip(out_idx, 0, M - 1)
        updated = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(emit, result,
                               lax.dynamic_index_in_dim(outputs, safe_idx,
                                                        keepdims=False)),
            safe_idx, axis=0)
        outputs = updated
        # Hop activations forward along the ring.
        current = lax.ppermute(result, axis, perm)
        return current, outputs

    from horovod_tpu.parallel._vma import match_vma

    # Zero-init carries typed varying like the stage weights/input so the
    # fori_loop carry types match under check_vma=True.
    vma_refs = (x, *jax.tree_util.tree_leaves(params))
    current0 = match_vma(jnp.zeros(micro_shape, x.dtype), *vma_refs)
    outputs0 = match_vma(jnp.zeros((M,) + micro_shape, x.dtype), *vma_refs)
    _, outputs = lax.fori_loop(0, n_ticks, tick, (current0, outputs0))

    # Only the last stage holds real outputs; replicate them to all chips
    # (masked psum = broadcast from the last stage). The sum rides the
    # exact-VJP conjugate: a raw psum would apply psum again in its
    # transpose and scale every upstream gradient by the stage count
    # (see parallel/tp.py tp_region_output; grad test
    # test_parallel.py::TestPipeline::test_gradients_match_sequential).
    from horovod_tpu.parallel.tp import sum_across

    mask = (rank == size - 1).astype(outputs.dtype)
    return sum_across(outputs * mask, axis)
