"""Ulysses-style sequence parallelism: all-to-all head/sequence resharding.

The second first-class long-context scheme (with ring attention): instead
of rotating K/V, one ``lax.all_to_all`` reshards [B, L/P, H, D] (sequence-
sharded) into [B, L, H/P, D] (head-sharded), full attention runs locally
per head group, and a second all-to-all reshards back. Communication is
2 all-to-alls of the activations regardless of sequence length — cheaper
than ring attention when H >= P and the sequence fits per-chip memory;
ring attention wins when L_local^2 dominates. Both ride the ICI.

Use inside shard_map with the sequence axis sharded.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
from jax import lax

from horovod_tpu.ops.attention import dot_product_attention
from horovod_tpu.parallel.logical import module_axis


def _seq_to_heads(x, axis: str):
    # [B, L/P, H, D] -> [B, L, H/P, D]
    return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)


def _heads_to_seq(x, axis: str):
    # [B, L, H/P, D] -> [B, L/P, H, D]
    return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(q, k, v, axis: Optional[str] = None,
                      causal: bool = False,
                      scale: Optional[float] = None,
                      attn_fn: Optional[Callable] = None,
                      use_flash: bool = False):
    """All-to-all sequence-parallel attention.

    Per-chip shapes [B, L_local, H, D] -> [B, L_local, H, D]; the head
    count must be divisible by the axis size. ``attn_fn(q, k, v, causal,
    scale)`` defaults to the reference jnp kernel; pass
    :func:`horovod_tpu.ops.attention.flash_attention` on TPU for the
    Pallas path (``use_flash=True`` is the shorthand). After the
    all-to-all the local view is the FULL sequence at global offset 0,
    so causal flash here runs the packed at-or-below-diagonal grid —
    the truncated-K/V-traffic causal path — with no offset plumbing.
    """
    axis = module_axis("seq", axis)
    size = lax.axis_size(axis)
    H = q.shape[2]
    if H % size != 0:
        raise ValueError(
            f"ulysses needs heads ({H}) divisible by axis size ({size}); "
            "use ring_attention for head counts below the mesh size")
    if use_flash and attn_fn is None:
        from horovod_tpu.ops.attention import flash_attention

        attn_fn = flash_attention
    qh = _seq_to_heads(q, axis)
    kh = _seq_to_heads(k, axis)
    vh = _seq_to_heads(v, axis)
    if attn_fn is None:
        out = dot_product_attention(qh, kh, vh, causal=causal, scale=scale)
    else:
        out = attn_fn(qh, kh, vh, causal=causal, scale=scale)
    return _heads_to_seq(out, axis)
