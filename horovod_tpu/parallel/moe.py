"""Expert parallelism: Switch-style top-1 MoE with all-to-all dispatch.

Beyond-reference capability (SURVEY §2.9: no EP in the reference). Experts
shard over an ``"ep"`` mesh axis (E_local = E / P per chip). Routing
builds dispatch/combine tensors from a top-1 softmax gate with capacity
dropping (Switch Transformer), then two ``lax.all_to_all``s move token
slots: tokens -> their expert's chip, expert outputs -> back. The einsum
formulation keeps everything dense for the MXU; dropped tokens pass
through via the residual (combine weights are zero for them).

Use inside shard_map with tokens sharded over the axis.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.logical import module_axis


def top1_routing(x, gate_w, num_experts: int, capacity: int):
    """Switch top-1 routing. x [T, D] -> (dispatch [T, E, C] one-hot,
    combine [T, E, C] gate-weighted, aux_loss scalar)."""
    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                       # [T]
    gate = jnp.max(probs, axis=-1)                            # [T]
    onehot = jax.nn.one_hot(expert, num_experts, dtype=jnp.float32)
    # Position of each token within its expert's queue.
    position = jnp.cumsum(onehot, axis=0) * onehot - 1.0      # [T, E]
    keep = (position >= 0) & (position < capacity)
    pos_clamped = jnp.clip(position, 0, capacity - 1).astype(jnp.int32)
    slot = jax.nn.one_hot(pos_clamped, capacity, dtype=jnp.float32)
    dispatch = onehot[..., None] * slot * keep[..., None]     # [T, E, C]
    combine = dispatch * gate[:, None, None]
    # Load-balancing auxiliary loss (Switch eq. 4).
    density = jnp.mean(onehot, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * num_experts
    return dispatch, combine, aux


def moe_layer(x, gate_w, expert_fn: Callable, expert_params,
              axis: Optional[str] = None, capacity_factor: float = 1.25,
              return_aux: bool = False):
    """Expert-parallel MoE layer inside shard_map.

    Args:
      x: this chip's tokens [T, D].
      gate_w: router weights [D, E] (replicated).
      expert_fn: ``(params_one_expert, tokens [N, D]) -> [N, D]``.
      expert_params: this chip's experts' params, leading axis E_local
        (pass stacked [E, ...] with ``P("ep")`` in_specs).
    Returns y [T, D] (+ aux loss when ``return_aux``).
    """
    axis = module_axis("expert", axis)
    size = lax.axis_size(axis)
    T, D = x.shape
    e_leaves = jax.tree_util.tree_leaves(expert_params)
    e_local = e_leaves[0].shape[0]
    num_experts = e_local * size
    capacity = max(1, math.ceil(T * capacity_factor / num_experts))

    dispatch, combine, aux = top1_routing(x, gate_w, num_experts, capacity)

    # [T, E, C] x [T, D] -> [E, C, D]: expert slots filled with tokens.
    slots = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    # Reshard tokens -> expert chips. Untiled all_to_all with split ==
    # concat == 0 is a chip-transpose: recv[s] = sent_by_chip_s[my_rank].
    # Chip r owns global experts [r*e_local, (r+1)*e_local); so with the
    # leading axis indexing destination chips, recv[s, le] holds chip s's
    # dispatched slots for my local expert le.
    slots = slots.reshape(size, e_local, capacity, D)
    recv = lax.all_to_all(slots, axis, split_axis=0, concat_axis=0,
                          tiled=False)                 # [P_src, e_local, C, D]
    # Experts process all sources' slots at once (one big MXU matmul per
    # expert instead of P small ones).
    tokens = recv.transpose(1, 0, 2, 3).reshape(e_local, size * capacity, D)
    out = jax.vmap(expert_fn)(expert_params, tokens.astype(x.dtype))
    out = out.astype(jnp.float32).reshape(e_local, size, capacity, D)
    out = out.transpose(1, 0, 2, 3)                    # [P_src, e_local, C, D]

    # Route back: the same chip-transpose returns processed slots to their
    # dispatching chip; reassembling the leading axes as (owner chip,
    # local expert) recovers the global expert index g = r*e_local + le.
    back = lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                          tiled=False)
    back = back.reshape(num_experts, capacity, D)
    y = jnp.einsum("tec,ecd->td", combine, back).astype(x.dtype)
    if return_aux:
        return y, aux
    return y
