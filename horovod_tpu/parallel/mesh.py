"""Mesh construction: 1-D data-parallel, hierarchical ICI x DCN, and
general multi-axis meshes for tp/pp/sp/ep.

The reference's communicator topology was MPI_COMM_WORLD split into
node-local and cross-node communicators to run hierarchical allreduce
(NCCL within a node, MPI across — reference operations.cc:1284-1436,
1760-1797). On TPU the same factorization is a 2-D mesh: a fast inner axis
laid out on the ICI (one slice / one host's chips) and a slow outer axis
over DCN (across slices/hosts). XLA then lowers a psum over ("dcn","ici")
into the reduce-scatter -> cross -> all-gather ladder the reference hand-
coded.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from horovod_tpu.common.exceptions import InvalidArgumentError
from horovod_tpu.parallel.logical import DCN_AXIS, ICI_AXIS


def make_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Build a named mesh with the given axis sizes.

    ``axes`` maps axis name -> size, in major-to-minor order; the product
    must equal the device count. Use -1 for at most one axis to absorb the
    remainder (like a reshape).
    """
    devices = list(devices) if devices is not None else list(jax.devices())
    n = len(devices)
    names = list(axes)
    sizes = list(axes.values())
    wild = [i for i, s in enumerate(sizes) if s == -1]
    if len(wild) > 1:
        raise InvalidArgumentError("at most one axis may be -1")
    fixed = math.prod(s for s in sizes if s != -1)
    if wild:
        if n % fixed != 0:
            raise InvalidArgumentError(
                f"{n} devices not divisible by {fixed}")
        sizes[wild[0]] = n // fixed
    if math.prod(sizes) != n:
        raise InvalidArgumentError(
            f"mesh {dict(zip(names, sizes))} needs {math.prod(sizes)} "
            f"devices, have {n}")
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def slice_topology(devices=None):
    """``(num_domains, chips_per_domain)`` of the device set's DCN
    topology: devices grouped by ``slice_index`` (multi-slice TPU
    runtimes expose it; the T5X ``create_hybrid_device_mesh`` signal,
    SNIPPETS.md [2]) or, when absent, by ``process_index`` (the
    reference's node boundary, operations.cc:1760-1797). Heterogeneous
    domain sizes raise — mirroring the reference's is_homogeneous
    degradation rule (operations.cc:1303-1315)."""
    devices = list(devices) if devices is not None else list(jax.devices())
    counts: Dict[int, int] = {}
    has_slice = any(getattr(d, "slice_index", None) is not None
                    for d in devices)
    for d in devices:
        key = (getattr(d, "slice_index", None) if has_slice
               else getattr(d, "process_index", 0))
        counts[key if key is not None else -1] = counts.get(
            key if key is not None else -1, 0) + 1
    sizes = set(counts.values())
    if len(sizes) > 1:
        raise InvalidArgumentError(
            "heterogeneous chips-per-domain layout; pass inner= "
            f"explicitly (saw {sorted(sizes)})")
    per = next(iter(sizes)) if sizes else 1
    return len(counts), per


def dcn_present(devices=None) -> bool:
    """True when the device set spans a DCN boundary (more than one
    slice/process domain) — what HOROVOD_HIERARCHICAL=auto keys off."""
    try:
        domains, _ = slice_topology(devices)
    except InvalidArgumentError:
        return True  # heterogeneous = definitely multi-domain
    return domains > 1


def hybrid_mesh(ici_axes: Optional[Dict[str, int]] = None,
                dcn_axes: Optional[Dict[str, int]] = None,
                devices=None) -> Mesh:
    """Two-level ICI x DCN mesh, the T5X ``create_hybrid_device_mesh``
    pattern (SNIPPETS.md [2]): DCN axes major (striding across slices),
    ICI axes minor (contiguous within a slice), so a collective over the
    ICI axes never crosses the data-center network and a collective over
    the DCN axes moves only already-reduced shards.

    ``ici_axes``/``dcn_axes`` map axis name -> size in major-to-minor
    order; the ICI product must equal chips-per-slice and the DCN
    product the slice count (both default to the detected
    :func:`slice_topology`, axes named "ici"/"dcn"). Devices are
    ordered slice-major so each slice's chips are contiguous on the
    flattened mesh — the layout the in-axis ladder
    (:func:`hierarchical_allreduce_in_axis` / fusion.py's hierarchical
    buckets) assumes for its ``axis_index_groups``.
    """
    devices = list(devices) if devices is not None else list(jax.devices())
    domains, per = slice_topology(devices)
    if ici_axes is None:
        ici_axes = {ICI_AXIS: per}
    if dcn_axes is None:
        dcn_axes = {DCN_AXIS: domains}
    ici_n = math.prod(ici_axes.values())
    dcn_n = math.prod(dcn_axes.values())
    if ici_n * dcn_n != len(devices):
        raise InvalidArgumentError(
            f"hybrid mesh {dict(dcn_axes)} x {dict(ici_axes)} needs "
            f"{ici_n * dcn_n} devices, have {len(devices)}")
    # On a REAL multi-domain topology the ICI axes must tile exactly one
    # slice (and the DCN axes the slice count) — an ICI axis spanning a
    # DCN boundary would silently run the "fast" legs over the slow
    # fabric. Single-domain device sets (the CPU virtual-mesh testing
    # path) may factor freely: every boundary there is virtual.
    if domains > 1 and (ici_n != per or dcn_n != domains):
        raise InvalidArgumentError(
            f"hybrid mesh ICI axes {dict(ici_axes)} x DCN axes "
            f"{dict(dcn_axes)} do not tile the detected topology of "
            f"{domains} domain(s) x {per} chip(s): ICI product must be "
            f"{per} and DCN product {domains}, or an ICI axis would "
            "cross a DCN boundary")
    # Slice-major device order: group by domain, concatenate.
    has_slice = any(getattr(d, "slice_index", None) is not None
                    for d in devices)
    keyed = sorted(
        devices,
        key=lambda d: ((getattr(d, "slice_index", 0) or 0) if has_slice
                       else getattr(d, "process_index", 0),
                       d.id))
    sizes = list(dcn_axes.values()) + list(ici_axes.values())
    names = tuple(dcn_axes) + tuple(ici_axes)
    arr = np.asarray(keyed).reshape(sizes)
    return Mesh(arr, names)


def hierarchical_mesh(devices=None, inner: Optional[int] = None,
                      outer_axis: str = DCN_AXIS,
                      inner_axis: str = ICI_AXIS) -> Mesh:
    """Two-level mesh for hierarchical collectives.

    ``inner`` defaults to the chips-per-process count, so the inner axis
    stays on one host's ICI domain and the outer axis crosses hosts over
    DCN — the reference's local_comm / cross_comm split
    (operations.cc:1760-1797). Homogeneity is required, mirroring the
    reference's is_homogeneous degradation rule (operations.cc:1303-1315).
    """
    devices = list(devices) if devices is not None else list(jax.devices())
    if inner is None:
        counts: Dict[int, int] = {}
        for d in devices:
            pid = getattr(d, "process_index", 0)
            counts[pid] = counts.get(pid, 0) + 1
        sizes = set(counts.values())
        if len(sizes) > 1:
            raise InvalidArgumentError(
                "heterogeneous chips-per-process layout; pass inner= "
                f"explicitly (saw {sorted(sizes)})")
        inner = next(iter(sizes)) if sizes else 1
    if inner <= 0 or len(devices) % inner != 0:
        raise InvalidArgumentError(
            f"inner size {inner} does not divide {len(devices)} devices")
    return make_mesh({outer_axis: len(devices) // inner, inner_axis: inner},
                     devices)


def inner_groups(size: int, inner: int):
    """Fast-domain (ICI) groups of a flat ``size`` axis: consecutive
    chips share a group, mirroring the reference's shared-memory
    local_comm split (operations.cc:1760-1797)."""
    return [[o * inner + i for i in range(inner)]
            for o in range(size // inner)]


def outer_groups(size: int, inner: int):
    """Slow-domain (DCN) groups: one per inner index, striding across the
    fast domains — the reference's per-local-rank cross_comm."""
    return [[o * inner + i for o in range(size // inner)]
            for i in range(inner)]


def hierarchical_ladder_in_axis(flat, axis: str, inner: int,
                                outer_exchange=None):
    """The two-level ladder INSIDE a flat 1-D SPMD axis, via
    ``axis_index_groups`` — no second mesh axis needed. This is the
    shared rung every hierarchical consumer runs (fusion.py's bucket
    path, the per-tensor wrapper below): reduce-scatter within the fast
    (ICI) group, exchange the 1/``inner`` shard across the slow (DCN)
    group, all-gather within the fast group. The cross-domain phase
    moves size/inner bytes per chip — the bandwidth property the
    reference's hierarchical design bought (operations.cc:1284-1436).

    ``flat`` must be 1-D with ``flat.size % inner == 0``.
    ``outer_exchange(shard, axis, outer_groups)`` replaces the default
    cross-domain ``lax.psum`` — fusion.py passes the quantized
    (int8/fp8) DCN wire exchange here. Returns the reduced flat array.
    """
    from jax import lax

    size = lax.axis_size(axis)
    ig = inner_groups(size, inner)
    og = outer_groups(size, inner)
    shards = flat.reshape(inner, -1)
    my_shard = lax.psum_scatter(shards, axis, scatter_dimension=0,
                                axis_index_groups=ig, tiled=False)
    if outer_exchange is None:
        my_shard = lax.psum(my_shard, axis, axis_index_groups=og)
    else:
        my_shard = outer_exchange(my_shard, axis, og)
    return lax.all_gather(my_shard, axis, axis=0,
                          axis_index_groups=ig).reshape(-1)


def hierarchical_allreduce_in_axis(x, axis: str, inner: int,
                                   average: bool = False):
    """Two-level allreduce of one tensor inside a flat 1-D SPMD axis — a
    thin pad/reshape wrapper over :func:`hierarchical_ladder_in_axis`
    (fusion.py's bucket path runs the same ladder over whole fused
    buckets)."""
    from jax import lax
    import jax.numpy as jnp

    size = lax.axis_size(axis)
    if inner <= 1 or inner >= size or size % inner != 0:
        out = lax.psum(x, axis)
        return out / size if average else out
    orig_shape = x.shape
    n = x.size
    pad = (-n) % inner
    flat = x.reshape(-1)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    full = hierarchical_ladder_in_axis(flat, axis, inner)
    if pad:
        full = full[:n]
    out = full.reshape(orig_shape)
    if average:
        out = out / size
    return out


def hierarchical_allgather_in_axis(x, axis: str, inner: int):
    """Two-phase allgather inside a flat 1-D SPMD axis (reference
    operations.cc:929-1032: node-local gather into a shared window, then
    cross-node exchange). Phase 1 gathers within the fast group; phase 2
    exchanges whole fast-group blocks across the slow group, yielding the
    same rank-major concatenation a flat all_gather produces."""
    from jax import lax

    size = lax.axis_size(axis)
    if inner <= 1 or inner >= size or size % inner != 0:
        return lax.all_gather(x, axis, tiled=True)
    block = lax.all_gather(x, axis, tiled=True,
                           axis_index_groups=inner_groups(size, inner))
    return lax.all_gather(block, axis, tiled=True,
                          axis_index_groups=outer_groups(size, inner))


def hierarchical_allreduce(x, outer_axis: str = DCN_AXIS,
                           inner_axis: str = ICI_AXIS, average: bool = False):
    """Two-phase allreduce over a hierarchical mesh, inside shard_map.

    Semantics of the reference's hierarchical path (operations.cc:
    1284-1436): reduce-scatter within the fast domain, reduce across the
    slow domain on 1/inner of the data per chip, all-gather within the
    fast domain. XLA emits exactly this ladder for a psum over both axes;
    we spell the phases explicitly so the inner/outer traffic split is
    auditable (and the outer phase moves count/inner bytes per chip, the
    property the reference's design bought).
    """
    from jax import lax

    inner_size = lax.axis_size(inner_axis)
    orig_shape = x.shape
    n = x.size
    pad = (-n) % inner_size
    flat = x.reshape(-1)
    if pad:
        import jax.numpy as jnp

        flat = jnp.pad(flat, (0, pad))
    # Phase 1: reduce-scatter on the ICI (fast) axis.
    shards = flat.reshape(inner_size, -1)
    my_shard = lax.psum_scatter(shards, inner_axis, scatter_dimension=0,
                                tiled=False)
    # Phase 2: allreduce the 1/inner shard across DCN (slow) axis.
    my_shard = lax.psum(my_shard, outer_axis)
    # Phase 3: all-gather on the ICI axis.
    full = lax.all_gather(my_shard, inner_axis, axis=0).reshape(-1)
    if pad:
        full = full[:n]
    out = full.reshape(orig_shape)
    if average:
        out = out / (inner_size * lax.axis_size(outer_axis))
    return out
