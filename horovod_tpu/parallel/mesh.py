"""Mesh construction: 1-D data-parallel, hierarchical ICI x DCN, and
general multi-axis meshes for tp/pp/sp/ep.

The reference's communicator topology was MPI_COMM_WORLD split into
node-local and cross-node communicators to run hierarchical allreduce
(NCCL within a node, MPI across — reference operations.cc:1284-1436,
1760-1797). On TPU the same factorization is a 2-D mesh: a fast inner axis
laid out on the ICI (one slice / one host's chips) and a slow outer axis
over DCN (across slices/hosts). XLA then lowers a psum over ("dcn","ici")
into the reduce-scatter -> cross -> all-gather ladder the reference hand-
coded.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from horovod_tpu.common.exceptions import InvalidArgumentError


def make_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Build a named mesh with the given axis sizes.

    ``axes`` maps axis name -> size, in major-to-minor order; the product
    must equal the device count. Use -1 for at most one axis to absorb the
    remainder (like a reshape).
    """
    devices = list(devices) if devices is not None else list(jax.devices())
    n = len(devices)
    names = list(axes)
    sizes = list(axes.values())
    wild = [i for i, s in enumerate(sizes) if s == -1]
    if len(wild) > 1:
        raise InvalidArgumentError("at most one axis may be -1")
    fixed = math.prod(s for s in sizes if s != -1)
    if wild:
        if n % fixed != 0:
            raise InvalidArgumentError(
                f"{n} devices not divisible by {fixed}")
        sizes[wild[0]] = n // fixed
    if math.prod(sizes) != n:
        raise InvalidArgumentError(
            f"mesh {dict(zip(names, sizes))} needs {math.prod(sizes)} "
            f"devices, have {n}")
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def hierarchical_mesh(devices=None, inner: Optional[int] = None,
                      outer_axis: str = "dcn",
                      inner_axis: str = "ici") -> Mesh:
    """Two-level mesh for hierarchical collectives.

    ``inner`` defaults to the chips-per-process count, so the inner axis
    stays on one host's ICI domain and the outer axis crosses hosts over
    DCN — the reference's local_comm / cross_comm split
    (operations.cc:1760-1797). Homogeneity is required, mirroring the
    reference's is_homogeneous degradation rule (operations.cc:1303-1315).
    """
    devices = list(devices) if devices is not None else list(jax.devices())
    if inner is None:
        counts: Dict[int, int] = {}
        for d in devices:
            pid = getattr(d, "process_index", 0)
            counts[pid] = counts.get(pid, 0) + 1
        sizes = set(counts.values())
        if len(sizes) > 1:
            raise InvalidArgumentError(
                "heterogeneous chips-per-process layout; pass inner= "
                f"explicitly (saw {sorted(sizes)})")
        inner = next(iter(sizes)) if sizes else 1
    if inner <= 0 or len(devices) % inner != 0:
        raise InvalidArgumentError(
            f"inner size {inner} does not divide {len(devices)} devices")
    return make_mesh({outer_axis: len(devices) // inner, inner_axis: inner},
                     devices)


def inner_groups(size: int, inner: int):
    """Fast-domain (ICI) groups of a flat ``size`` axis: consecutive
    chips share a group, mirroring the reference's shared-memory
    local_comm split (operations.cc:1760-1797)."""
    return [[o * inner + i for i in range(inner)]
            for o in range(size // inner)]


def outer_groups(size: int, inner: int):
    """Slow-domain (DCN) groups: one per inner index, striding across the
    fast domains — the reference's per-local-rank cross_comm."""
    return [[o * inner + i for o in range(size // inner)]
            for i in range(inner)]


def hierarchical_allreduce_in_axis(x, axis: str, inner: int,
                                   average: bool = False):
    """Two-level allreduce INSIDE a flat 1-D SPMD axis, via
    ``axis_index_groups`` — no second mesh axis needed.

    Same ladder as the reference's hierarchical path (operations.cc:
    1284-1436): reduce-scatter within the fast (ICI) group, allreduce the
    1/inner shard across the slow (DCN) group, all-gather within the fast
    group. The cross-domain phase moves size/inner bytes per chip — the
    bandwidth property the reference's design bought.
    """
    from jax import lax
    import jax.numpy as jnp

    size = lax.axis_size(axis)
    if inner <= 1 or inner >= size or size % inner != 0:
        out = lax.psum(x, axis)
        return out / size if average else out
    ig = inner_groups(size, inner)
    og = outer_groups(size, inner)
    orig_shape = x.shape
    n = x.size
    pad = (-n) % inner
    flat = x.reshape(-1)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    shards = flat.reshape(inner, -1)
    my_shard = lax.psum_scatter(shards, axis, scatter_dimension=0,
                                axis_index_groups=ig, tiled=False)
    my_shard = lax.psum(my_shard, axis, axis_index_groups=og)
    full = lax.all_gather(my_shard, axis, axis=0,
                          axis_index_groups=ig).reshape(-1)
    if pad:
        full = full[:n]
    out = full.reshape(orig_shape)
    if average:
        out = out / size
    return out


def hierarchical_allgather_in_axis(x, axis: str, inner: int):
    """Two-phase allgather inside a flat 1-D SPMD axis (reference
    operations.cc:929-1032: node-local gather into a shared window, then
    cross-node exchange). Phase 1 gathers within the fast group; phase 2
    exchanges whole fast-group blocks across the slow group, yielding the
    same rank-major concatenation a flat all_gather produces."""
    from jax import lax

    size = lax.axis_size(axis)
    if inner <= 1 or inner >= size or size % inner != 0:
        return lax.all_gather(x, axis, tiled=True)
    block = lax.all_gather(x, axis, tiled=True,
                           axis_index_groups=inner_groups(size, inner))
    return lax.all_gather(block, axis, tiled=True,
                          axis_index_groups=outer_groups(size, inner))


def hierarchical_allreduce(x, outer_axis: str = "dcn",
                           inner_axis: str = "ici", average: bool = False):
    """Two-phase allreduce over a hierarchical mesh, inside shard_map.

    Semantics of the reference's hierarchical path (operations.cc:
    1284-1436): reduce-scatter within the fast domain, reduce across the
    slow domain on 1/inner of the data per chip, all-gather within the
    fast domain. XLA emits exactly this ladder for a psum over both axes;
    we spell the phases explicitly so the inner/outer traffic split is
    auditable (and the outer phase moves count/inner bytes per chip, the
    property the reference's design bought).
    """
    from jax import lax

    inner_size = lax.axis_size(inner_axis)
    orig_shape = x.shape
    n = x.size
    pad = (-n) % inner_size
    flat = x.reshape(-1)
    if pad:
        import jax.numpy as jnp

        flat = jnp.pad(flat, (0, pad))
    # Phase 1: reduce-scatter on the ICI (fast) axis.
    shards = flat.reshape(inner_size, -1)
    my_shard = lax.psum_scatter(shards, inner_axis, scatter_dimension=0,
                                tiled=False)
    # Phase 2: allreduce the 1/inner shard across DCN (slow) axis.
    my_shard = lax.psum(my_shard, outer_axis)
    # Phase 3: all-gather on the ICI axis.
    full = lax.all_gather(my_shard, inner_axis, axis=0).reshape(-1)
    if pad:
        full = full[:n]
    out = full.reshape(orig_shape)
    if average:
        out = out / (inner_size * lax.axis_size(outer_axis))
    return out
