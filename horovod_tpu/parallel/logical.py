"""The logical-axis sharding layer: one mesh factory + one rules table.

Before this module, every parallelism module hand-rolled its shardings:
``parallel/{spmd,tp,pipeline,ulysses,ring_attention,moe}.py`` each named
mesh axes by string convention, and hvdlint HVD008's suppression
inventory (18 findings across 8 files) was the coupling made visible.
This module is the T5X partitioning design (SNIPPETS.md [1][3]) applied
to that work list:

* **One vocabulary.** The physical axis names live HERE and only here —
  every other module imports them (``DATA_AXIS``/``ICI_AXIS``/
  ``DCN_AXIS`` and the per-role spellings below). HVD008 now hard-fails
  on any raw ``"hvd"``/``"ici"``/``"dcn"`` literal anywhere else.
* **One mesh factory.** :class:`LogicalMesh` builds the physical mesh
  from ``dp=8,tp=4,sp=2``-style axis stacks, layered on PR-10's
  :func:`~horovod_tpu.parallel.mesh.hybrid_mesh`/``slice_topology`` so
  DCN-aware placement falls out for free on multi-slice topologies, and
  falling back to a plain :func:`~horovod_tpu.parallel.mesh.make_mesh`
  on single-domain device sets (the CPU virtual-device testing path —
  the T5X ``cpu_fallback`` move, SNIPPETS.md [1]).
* **One rules table.** Logical tensor-dimension names (``batch``,
  ``heads``, ``embed``, ``mlp``, ``seq``, ``expert``, ``stage``, ...)
  map to physical mesh axes through an ordered rules registry; models
  annotate dimensions logically and :meth:`LogicalMesh.spec` resolves
  them against whatever stack is bound — a rule whose physical axis is
  absent from the mesh resolves to replicated, so any model composes
  with any parallelism stack.

The parallelism modules stay thin shims: their ``axis=`` parameters now
default to the bound mesh's role resolution (:func:`module_axis`), with
the historical per-module spellings (``"tp"``/``"pp"``/``"sp"``/
``"ep"``/``DATA_AXIS``) as the unbound fallback — bit-for-bit the
pre-registry behavior, equivalence-pinned in tests/test_logical.py.

Statically verified: hvdverify's HVV201 reconciles a program's declared
shardings against this rules table, HVV202 rejects collectives over
axes the bound LogicalMesh does not define, and HVV203 pins composed
stacks' collective schedules op-identical to the per-module reference
traces (docs/static_analysis.md).
"""

from __future__ import annotations

import contextlib
import math
from typing import Dict, List, Optional, Sequence, Tuple

from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.common.exceptions import InvalidArgumentError

# --------------------------------------------------------------------------
# The axis vocabulary. The ONE definition site of the physical axis
# spellings — hvdlint HVD008 flags these literals everywhere else (the
# rule's own vocabulary set in tools/hvdlint/rules.py mirrors this), so
# the suppressions below are the only shipped ones outside that rule.

#: The flat data-parallel axis of the default 1-D mesh (every chip a rank).
DATA_AXIS = "hvd"  # hvdlint: disable=HVD008 (logical.py owns the axis vocabulary)
#: Fast-domain axis of the hybrid ICI x DCN mesh (within one slice).
ICI_AXIS = "ici"  # hvdlint: disable=HVD008 (logical.py owns the axis vocabulary)
#: Slow-domain axis of the hybrid mesh (across slices, over DCN).
DCN_AXIS = "dcn"  # hvdlint: disable=HVD008 (logical.py owns the axis vocabulary)

#: Physical axis spelling per parallelism role — the historical
#: per-module defaults, now named once. Roles are what the parallelism
#: modules ask for (:func:`module_axis`); logical axis NAMES (below) are
#: what model tensors are annotated with.
ROLE_AXES: Dict[str, str] = {
    "data": "dp",
    "tensor": "tp",
    "seq": "sp",
    "stage": "pp",
    "expert": "ep",
}

#: Unbound-fallback spelling per role: what each module's ``axis=``
#: parameter defaulted to before the registry existed. ``data`` falls
#: back to the flat 1-D mesh axis, not "dp" — the spmd harness predates
#: multi-axis stacks.
_LEGACY_ROLE_AXES: Dict[str, str] = dict(ROLE_AXES, data=DATA_AXIS)

#: The default logical-axis rules table (T5X-style; SNIPPETS.md [3] is
#: the GPT-J sibling). Ordered: the FIRST rule whose physical axis the
#: bound mesh defines wins; a ``None`` physical axis means replicated.
#: ``batch`` tries the composed-stack spelling first and falls back to
#: the flat 1-D harness axis so the same annotations resolve under both.
DEFAULT_RULES: Tuple[Tuple[str, Optional[str]], ...] = (
    ("batch", ROLE_AXES["data"]),
    ("batch", DATA_AXIS),
    ("heads", ROLE_AXES["tensor"]),
    ("kv", None),
    ("embed", None),
    ("mlp", ROLE_AXES["tensor"]),
    ("vocab", ROLE_AXES["tensor"]),
    ("seq", ROLE_AXES["seq"]),
    ("expert", ROLE_AXES["expert"]),
    ("stage", ROLE_AXES["stage"]),
)

#: Logical axis names each role may carry collectives for — how
#: :meth:`LogicalMesh.role_axis` resolves a role through a CUSTOM rules
#: table whose physical spellings differ from :data:`ROLE_AXES`.
_ROLE_LOGICAL: Dict[str, Tuple[str, ...]] = {
    "data": ("batch",),
    "tensor": ("heads", "mlp", "vocab"),
    "seq": ("seq",),
    "stage": ("stage",),
    "expert": ("expert",),
}

#: Canonical axis order of the config string (unknown axes sort after,
#: alphabetically) — `dp=8,tp=4,sp=2` is canonical, `tp=4,dp=8` is not.
_CANONICAL_ORDER: Tuple[str, ...] = (
    ROLE_AXES["data"], ROLE_AXES["tensor"], ROLE_AXES["seq"],
    ROLE_AXES["stage"], ROLE_AXES["expert"], DATA_AXIS, ICI_AXIS,
    DCN_AXIS)


# ------------------------------------------------------------ config string


def parse_mesh_config(config: str) -> Dict[str, int]:
    """Parse the canonical mesh config string (``"dp=8,tp=4,sp=2"``) into
    an ordered ``{axis: size}`` dict — the hvdplan input format (ROADMAP
    item 5a) and ``bench.py --mesh``'s argument. ``-1`` is the
    :func:`~horovod_tpu.parallel.mesh.make_mesh` wildcard (at most one).
    """
    axes: Dict[str, int] = {}
    if not isinstance(config, str) or not config.strip():
        raise InvalidArgumentError(
            f"empty mesh config (expected e.g. 'dp=8,tp=4'): {config!r}")
    for part in config.split(","):
        part = part.strip()
        if "=" not in part:
            raise InvalidArgumentError(
                f"mesh config entry {part!r} is not name=size "
                f"(in {config!r})")
        name, _, size_s = part.partition("=")
        name = name.strip()
        if not name.isidentifier():
            raise InvalidArgumentError(
                f"mesh axis name {name!r} is not an identifier "
                f"(in {config!r})")
        if name in axes:
            raise InvalidArgumentError(
                f"duplicate mesh axis {name!r} in {config!r}")
        try:
            size = int(size_s.strip())
        except ValueError:
            raise InvalidArgumentError(
                f"mesh axis size {size_s!r} is not an integer "
                f"(in {config!r})") from None
        if size < 1 and size != -1:
            raise InvalidArgumentError(
                f"mesh axis {name}={size} must be >= 1 (or -1 wildcard)")
        axes[name] = size
    return axes


def format_mesh_config(axes: Dict[str, int]) -> str:
    """Render ``{axis: size}`` as the CANONICAL config string: known
    axes in dp/tp/sp/pp/ep order, unknown axes after them alphabetically
    — so two spellings of the same stack stamp identically into bench
    records."""
    def key(name: str):
        try:
            return (0, _CANONICAL_ORDER.index(name), name)
        except ValueError:
            return (1, 0, name)

    return ",".join(f"{n}={int(axes[n])}" for n in sorted(axes, key=key))


# --------------------------------------------------------------- the mesh


class LogicalMesh:
    """One physical mesh + one logical-axis rules table.

    ``axes`` maps physical axis name -> size in major-to-minor order
    (``-1`` wildcard as in :func:`~horovod_tpu.parallel.mesh.make_mesh`).
    On a multi-slice (DCN-present) device set the axes are split between
    the DCN and ICI levels of :func:`~horovod_tpu.parallel.mesh.
    hybrid_mesh` — leading axes go DCN-major until the slice count is
    consumed, the rest tile the slice — so ``dp=2,tp=4`` on a 2-slice
    topology puts dp across slices and tp on the ICI. Single-domain
    device sets (all CPU test meshes) build a plain ``make_mesh`` over
    the first ``prod(axes)`` devices: the virtual-device fallback.
    """

    def __init__(self, axes: Dict[str, int], *,
                 rules: Sequence[Tuple[str, Optional[str]]] = DEFAULT_RULES,
                 devices=None):
        from horovod_tpu.parallel import mesh as _mesh

        if not axes:
            raise InvalidArgumentError("LogicalMesh needs at least one axis")
        self.rules: Tuple[Tuple[str, Optional[str]], ...] = tuple(
            (str(l), p) for l, p in rules)
        import jax

        devices = (list(devices) if devices is not None
                   else list(jax.devices()))
        sizes = self._resolve_wildcard(dict(axes), len(devices))
        want = math.prod(sizes.values())
        if want > len(devices):
            # Fail-fast with the real arithmetic — without this the
            # overshoot surfaces as a cryptic make_mesh reshape error
            # (or worse, at first compile inside a consumer's jit).
            raise InvalidArgumentError(
                f"mesh axes {format_mesh_config(sizes)} need {want} "
                f"device(s) but only {len(devices)} are available")
        if want < len(devices):
            # Virtual sub-mesh (tests bind dp=2,tp=4 on however many
            # devices the host exposes): take a prefix, like the
            # hvdverify registry's _submesh.
            devices = devices[:want]
        if _mesh.dcn_present(devices):
            self.mesh = self._hybrid(sizes, devices, _mesh)
        else:
            self.mesh = _mesh.make_mesh(sizes, devices)
        self.axes: Dict[str, int] = {
            name: self.mesh.shape[name] for name in self.mesh.axis_names}

    @staticmethod
    def _resolve_wildcard(axes: Dict[str, int], n_devices: int
                          ) -> Dict[str, int]:
        wild = [name for name, s in axes.items() if s == -1]
        if len(wild) > 1:
            raise InvalidArgumentError("at most one axis may be -1")
        if wild:
            fixed = math.prod(s for s in axes.values() if s != -1)
            if fixed == 0 or n_devices % fixed != 0:
                raise InvalidArgumentError(
                    f"{n_devices} devices not divisible by {fixed}")
            axes[wild[0]] = n_devices // fixed
        return axes

    @staticmethod
    def _hybrid(sizes: Dict[str, int], devices, _mesh) -> Mesh:
        """Split the axis stack at the slice boundary: leading (major)
        axes multiply out to the slice count and go DCN; the rest tile
        one slice's chips and go ICI."""
        domains, per = _mesh.slice_topology(devices)
        dcn_axes: Dict[str, int] = {}
        acc = 1
        names = list(sizes)
        i = 0
        while i < len(names) and acc < domains:
            name = names[i]
            dcn_axes[name] = sizes[name]
            acc *= sizes[name]
            i += 1
        ici_axes = {name: sizes[name] for name in names[i:]}
        if acc != domains:
            raise InvalidArgumentError(
                f"mesh axes {sizes} do not factor at the slice boundary "
                f"of {domains} domain(s) x {per} chip(s): leading axes "
                f"multiply to {acc}, need {domains}")
        return _mesh.hybrid_mesh(ici_axes=ici_axes or None,
                                 dcn_axes=dcn_axes or None,
                                 devices=devices)

    @classmethod
    def from_config(cls, config: str, *,
                    rules: Sequence[Tuple[str, Optional[str]]]
                    = DEFAULT_RULES,
                    devices=None) -> "LogicalMesh":
        """Build from the canonical config string (``"dp=8,tp=4"``)."""
        return cls(parse_mesh_config(config), rules=rules, devices=devices)

    # ----------------------------------------------------------- resolution

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    @property
    def config(self) -> str:
        """The canonical config string of this mesh's axis stack."""
        return format_mesh_config(self.axes)

    def defines(self, axis: str) -> bool:
        """True when ``axis`` is a physical axis of this mesh — what
        hvdverify's HVV202 checks every traced collective against."""
        return axis in self.axes

    def axis(self, logical: str) -> Optional[str]:
        """Physical mesh axis for one logical axis name, via the first
        rule whose physical axis this mesh defines; ``None`` =
        replicated. Unknown logical names raise — a RAW physical axis
        here is exactly the coupling this layer removes (and the hvdlint
        HVD008 fixture shape)."""
        known = False
        for name, phys in self.rules:
            if name != logical:
                continue
            known = True
            if phys is None:
                return None
            if phys in self.axes:
                return phys
        if not known:
            raise InvalidArgumentError(
                f"unknown logical axis {logical!r}: not in the rules "
                f"table (known: {sorted({n for n, _ in self.rules})})")
        return None

    def spec(self, *logical_axes: Optional[str]) -> P:
        """Resolve logical tensor-dimension names to a PartitionSpec:
        ``spec("batch", None, "heads")`` -> e.g. ``P("dp", None, "tp")``
        on a dp x tp stack, ``P(None, None, None)``-free replication for
        dims whose rules map nowhere on this mesh."""
        resolved = [None if name is None else self.axis(name)
                    for name in logical_axes]
        # One physical axis may shard at most one dimension.
        used = [a for a in resolved if a is not None]
        dupes = {a for a in used if used.count(a) > 1}
        if dupes:
            raise InvalidArgumentError(
                f"logical axes {logical_axes} map {sorted(dupes)} onto "
                "more than one tensor dimension")
        return P(*resolved)

    def role_axis(self, role: str) -> Optional[str]:
        """Physical mesh axis for a parallelism ROLE ('data', 'tensor',
        'seq', 'stage', 'expert'): the conventional spelling when the
        mesh defines it, else the first rules-mapped logical axis of the
        role, else the flat 1-D axis for 'data', else ``None``."""
        if role not in _ROLE_LOGICAL:
            raise InvalidArgumentError(
                f"unknown parallelism role {role!r} "
                f"(known: {sorted(_ROLE_LOGICAL)})")
        conventional = ROLE_AXES[role]
        if conventional in self.axes:
            return conventional
        for logical in _ROLE_LOGICAL[role]:
            phys = self.axis(logical)
            if phys is not None:
                return phys
        if role == "data" and DATA_AXIS in self.axes:
            return DATA_AXIS
        return None


# ------------------------------------------------------------- bound mesh

_BOUND: List[LogicalMesh] = []


def bind(lm: LogicalMesh):
    """Context manager binding ``lm`` as the current logical mesh: the
    parallelism shims resolve their default axes against it
    (:func:`module_axis`), and hvdverify's HVV202 checks traced
    collectives against its axis set."""
    @contextlib.contextmanager
    def _ctx():
        _BOUND.append(lm)
        try:
            yield lm
        finally:
            _BOUND.pop()
    return _ctx()


def current_logical_mesh() -> Optional[LogicalMesh]:
    """The innermost :func:`bind`-ed mesh, or ``None``."""
    return _BOUND[-1] if _BOUND else None


def module_axis(role: str, override: Optional[str] = None) -> str:
    """Resolve a parallelism module's collective axis: an explicit
    ``axis=`` argument wins (the thin-shim contract — passing the
    historical literal is bit-for-bit the pre-registry path), else the
    bound LogicalMesh's role resolution, else the legacy per-module
    spelling. Raises when a bound mesh defines no axis for the role —
    composing a module onto a stack that cannot host it is a config
    error, not a silent fallback."""
    if override is not None:
        return override
    lm = current_logical_mesh()
    if lm is not None:
        axis = lm.role_axis(role)
        if axis is None:
            raise InvalidArgumentError(
                f"bound LogicalMesh {lm.config!r} defines no axis for "
                f"role {role!r}; add the axis to the mesh or pass axis= "
                "explicitly")
        return axis
    return _LEGACY_ROLE_AXES[role]


def logical_partition_specs(tree_logical_axes, lm: Optional[LogicalMesh]
                            = None):
    """Map a pytree of logical-axis tuples to PartitionSpecs via the
    (given or bound) mesh — the T5X ``logical_to_mesh_axes`` shape."""
    import jax

    lm = lm or current_logical_mesh()
    if lm is None:
        raise InvalidArgumentError(
            "logical_partition_specs needs a LogicalMesh (bind one or "
            "pass lm=)")
    return jax.tree_util.tree_map(
        lambda dims: lm.spec(*dims),
        tree_logical_axes,
        is_leaf=lambda x: isinstance(x, tuple))
