"""Parallelism strategies over device meshes.

The reference supported exactly one strategy — data parallelism (SURVEY §2.9)
— delegated to MPI/NCCL rings. Here DP is one axis of a general
``jax.sharding.Mesh``; this package adds the TPU-first strategies the
hardware makes natural: tensor parallelism, sequence/context parallelism
(ring attention, Ulysses all-to-all), pipeline parallelism, and expert
parallelism, plus the hierarchical ICI x DCN mesh that replaces the
reference's node-local/cross-node communicator split.
"""

from horovod_tpu.parallel.logical import (  # noqa: F401
    DATA_AXIS,
    DCN_AXIS,
    DEFAULT_RULES,
    ICI_AXIS,
    LogicalMesh,
    bind,
    current_logical_mesh,
    format_mesh_config,
    logical_partition_specs,
    module_axis,
    parse_mesh_config,
)
from horovod_tpu.parallel.spmd import axis_size, spmd, spmd_run  # noqa: F401
from horovod_tpu.parallel.mesh import (  # noqa: F401
    hierarchical_allreduce,
    hierarchical_mesh,
    make_mesh,
)
from horovod_tpu.parallel.ring_attention import ring_attention  # noqa: F401
from horovod_tpu.parallel.ulysses import ulysses_attention  # noqa: F401
from horovod_tpu.parallel.tp import (  # noqa: F401
    column_parallel,
    row_parallel,
    shard_columns,
    shard_rows,
    sum_across,
    tp_mlp,
    tp_region_input,
    tp_region_output,
)
from horovod_tpu.parallel.pipeline import pipeline_apply  # noqa: F401
from horovod_tpu.parallel.moe import moe_layer, top1_routing  # noqa: F401
