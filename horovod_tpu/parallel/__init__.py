"""Parallelism strategies over device meshes.

The reference supported exactly one strategy — data parallelism (SURVEY §2.9)
— delegated to MPI/NCCL rings. Here DP is one axis of a general
``jax.sharding.Mesh``; this package adds the TPU-first strategies the
hardware makes natural: tensor parallelism, sequence/context parallelism
(ring attention, all-to-all), pipeline parallelism, and expert parallelism.
"""

from horovod_tpu.parallel.spmd import axis_size, spmd, spmd_run  # noqa: F401
