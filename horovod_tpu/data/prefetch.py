"""Device prefetch: overlap host→device transfer with compute.

On TPU the HBM transfer of batch N+1 can ride the DMA engines while
batch N's step executes — but only if the transfer is *issued* before
the step blocks. ``jax.device_put`` is asynchronous, so a small look-
ahead queue of issued-but-unconsumed batches achieves the overlap with
no threads (the flax-examples prefetch idiom, generalized to shardings).
"""

from __future__ import annotations

import collections
from typing import Iterable, Iterator, Optional

import jax


def prefetch_to_device(
    iterator: Iterable,
    size: int = 2,
    sharding: Optional[object] = None,
) -> Iterator:
    """Yield items from ``iterator`` with ``size`` transfers in flight.

    Each item (a pytree of host arrays) is moved with ``jax.device_put``
    — to ``sharding`` if given (e.g. ``NamedSharding(mesh, P("hvd"))``
    to scatter the batch straight to its mesh layout), else to the
    default device. ``size=2`` double-buffers: one batch computing, one
    in flight.
    """
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    queue: collections.deque = collections.deque()
    it = iter(iterator)

    def put(item):
        if sharding is not None:
            return jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sharding), item
            )
        return jax.tree_util.tree_map(jax.device_put, item)

    try:
        while len(queue) < size:
            queue.append(put(next(it)))
    except StopIteration:
        pass
    while queue:
        yield queue.popleft()
        try:
            queue.append(put(next(it)))
        except StopIteration:
            pass
