"""Device prefetch: overlap host→device transfer with compute.

On TPU the HBM transfer of batch N+1 can ride the DMA engines while
batch N's step executes — but only if the transfer is *issued* before
the step blocks. ``jax.device_put`` is asynchronous, so a small look-
ahead queue of issued-but-unconsumed batches achieves the overlap with
no threads (the flax-examples prefetch idiom, generalized to shardings).
"""

from __future__ import annotations

import collections
import itertools
from typing import Iterable, Iterator, Optional

import jax
import numpy as np


def prefetch_to_device(
    iterator: Iterable,
    size: int = 2,
    sharding: Optional[object] = None,
) -> Iterator:
    """Yield items from ``iterator`` with ``size`` transfers in flight.

    Each item (a pytree of host arrays) is moved with ``jax.device_put``
    — to ``sharding`` if given (e.g. ``NamedSharding(mesh, P("hvd"))``
    to scatter the batch straight to its mesh layout), else to the
    default device. ``size=2`` double-buffers: one batch computing, one
    in flight.
    """
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    queue: collections.deque = collections.deque()
    it = iter(iterator)

    def put(item):
        if sharding is not None:
            return jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sharding), item
            )
        return jax.tree_util.tree_map(jax.device_put, item)

    try:
        while len(queue) < size:
            queue.append(put(next(it)))
    except StopIteration:
        pass
    while queue:
        yield queue.popleft()
        try:
            queue.append(put(next(it)))
        except StopIteration:
            pass


def window_batches(
    iterator: Iterable,
    steps_per_dispatch: int,
) -> Iterator:
    """Group consecutive batches into stacked K-step windows.

    Host-side ``np.stack`` per leaf: every leaf of each yielded pytree
    carries a leading window axis of length ``steps_per_dispatch`` (the
    trailing window may be shorter when the iterator does not divide
    evenly — no batch is dropped). Order is preserved: window ``i``
    holds batches ``[i*K, (i+1)*K)`` in iteration order.
    """
    if steps_per_dispatch < 1:
        raise ValueError(
            f"steps_per_dispatch must be >= 1, got {steps_per_dispatch}")
    it = iter(iterator)
    while True:
        group = list(itertools.islice(it, steps_per_dispatch))
        if not group:
            return
        yield jax.tree_util.tree_map(
            lambda *leaves: np.stack(leaves), *group)


def prefetch_windows(
    iterator: Iterable,
    steps_per_dispatch: int,
    size: int = 2,
    sharding: Optional[object] = None,
) -> Iterator:
    """Double-buffered K-batch device stager for multi-step windows.

    The feeding half of :func:`horovod_tpu.jax.window.run_steps`: K
    consecutive batches are stacked on the host
    (:func:`window_batches`) and moved with one asynchronous
    ``jax.device_put`` per window — ``sharding`` should describe the
    STACKED layout (e.g. ``NamedSharding(mesh, P(None, "hvd"))``: window
    axis replicated, batch axis scattered). ``size=2`` double-buffers at
    window granularity: window N computes while window N+1's
    host->device copy rides the DMA engines.

    ``steps_per_dispatch == 1`` is the identity path — exactly
    :func:`prefetch_to_device`, no window axis added.
    """
    if steps_per_dispatch < 1:
        raise ValueError(
            f"steps_per_dispatch must be >= 1, got {steps_per_dispatch}")
    source = (iterator if steps_per_dispatch == 1
              else window_batches(iterator, steps_per_dispatch))
    yield from prefetch_to_device(source, size=size, sharding=sharding)
