"""Deterministic per-rank data sharding (DistributedSampler analogue).

Semantics follow torch's ``DistributedSampler`` as used by the
reference's examples: each epoch, a seeded global permutation is split
into ``size`` disjoint strided slices; the dataset is padded by
repeating leading samples so every rank sees the same number of batches
(collectives would otherwise deadlock on ragged epochs — the same
reason torch's sampler pads).
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

import numpy as np


def _resolve(rank: Optional[int], size: Optional[int]):
    if rank is None or size is None:
        from horovod_tpu.common import basics

        if basics.is_initialized():
            rank = basics.process_rank() if rank is None else rank
            size = basics.process_count() if size is None else size
        else:
            rank = 0 if rank is None else rank
            size = 1 if size is None else size
    return rank, size


def shard_indices(
    n: int,
    epoch: int = 0,
    rank: Optional[int] = None,
    size: Optional[int] = None,
    shuffle: bool = True,
    seed: int = 0,
    drop_remainder: bool = False,
) -> np.ndarray:
    """This rank's sample indices for ``epoch`` over a dataset of ``n``.

    All ranks use the same seeded permutation (seed + epoch), so the
    union over ranks covers the dataset exactly once (up to pad/drop).
    With ``drop_remainder`` the tail that does not divide ``size`` is
    dropped; otherwise leading samples repeat as padding.
    """
    rank, size = _resolve(rank, size)
    if not 0 <= rank < size:
        raise ValueError(f"rank {rank} out of range for size {size}")
    order = (
        np.random.RandomState(seed + epoch).permutation(n)
        if shuffle
        else np.arange(n)
    )
    if drop_remainder:
        usable = (n // size) * size
        order = order[:usable]
    elif n % size:
        # Cyclic repeat up to the next multiple of size — handles any
        # pad length, including n < size (torch's sampler repeats the
        # same way so every rank gets ceil(n/size) samples).
        order = np.resize(order, ((n + size - 1) // size) * size)
    return order[rank::size]


class DistributedSampler:
    """Object form of :func:`shard_indices`, API-compatible with the
    torch sampler the reference's examples used: iterate for indices,
    ``set_epoch`` to reshuffle."""

    def __init__(self, n: int, rank: Optional[int] = None,
                 size: Optional[int] = None, shuffle: bool = True,
                 seed: int = 0, drop_remainder: bool = False):
        self.n = int(n)
        self.rank, self.size = _resolve(rank, size)
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)

    def __iter__(self) -> Iterator[int]:
        return iter(
            shard_indices(self.n, self.epoch, self.rank, self.size,
                          self.shuffle, self.seed, self.drop_remainder)
        )

    def __len__(self) -> int:
        if self.drop_remainder:
            return self.n // self.size
        return -(-self.n // self.size)


def iterate_sharded(
    arrays: dict,
    batch_size: int,
    epoch: int = 0,
    rank: Optional[int] = None,
    size: Optional[int] = None,
    shuffle: bool = True,
    seed: int = 0,
):
    """Yield this rank's ``batch_size`` batches (dict of numpy slices)
    for one epoch over same-length arrays. Batches that do not fill are
    dropped (static shapes: a ragged final batch would retrace the jit
    step)."""
    lengths = {k: len(v) for k, v in arrays.items()}
    if len(set(lengths.values())) != 1:
        raise ValueError(f"array lengths differ: {lengths}")
    n = next(iter(lengths.values()))
    idx = shard_indices(n, epoch, rank, size, shuffle, seed)
    for start in range(0, len(idx) - batch_size + 1, batch_size):
        sel = idx[start : start + batch_size]
        yield {k: v[sel] for k, v in arrays.items()}
