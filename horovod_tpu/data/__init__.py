"""Data sharding + device prefetch utilities.

The reference delegated input pipelines to the frameworks and its
examples leaned on ``torch.utils.data.distributed.DistributedSampler``
(reference examples/pytorch_mnist.py) — every rank reads a disjoint
1/size slice, reshuffled per epoch. This module is that piece for the
jax lanes, plus the device-feeding half that matters on TPU: keeping
the next batch's host→device transfer in flight while the current step
runs, so input never serializes with compute.
"""

from horovod_tpu.data.sharding import (
    DistributedSampler,
    iterate_sharded,
    shard_indices,
)
from horovod_tpu.data.prefetch import (
    prefetch_to_device,
    prefetch_windows,
    window_batches,
)

__all__ = [
    "DistributedSampler",
    "shard_indices",
    "iterate_sharded",
    "prefetch_to_device",
    "prefetch_windows",
    "window_batches",
]
